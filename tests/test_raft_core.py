"""Tier-1 consensus core tests: elections, replication, commit, votes,
membership, snapshots — behavioral port of the reference suite
(raft/raft_test.go) against the scalar oracle."""
import pytest

from etcd_tpu import raftpb
from etcd_tpu.raftpb import (ConfChange, ConfChangeType, ConfState, Entry,
                             EntryType, HardState, Message, MessageType,
                             Snapshot, SnapshotMetadata, StateType)
from etcd_tpu.raft.core import Config, Raft, ProposalDroppedError
from etcd_tpu.raft.progress import Inflights, Progress, ProgressState
from etcd_tpu.raft.storage import MemoryStorage

from tests.raft_fixtures import (NOP_STEPPER, Network, ents_with_terms, msg,
                                 new_test_raft, next_ents, read_messages)

HUP = MessageType.HUP
PROP = MessageType.PROP
APP = MessageType.APP
APP_RESP = MessageType.APP_RESP
VOTE = MessageType.VOTE
VOTE_RESP = MessageType.VOTE_RESP
HEARTBEAT = MessageType.HEARTBEAT
HEARTBEAT_RESP = MessageType.HEARTBEAT_RESP
BEAT = MessageType.BEAT
SNAP = MessageType.SNAP


def hup(i):
    return msg(HUP, frm=i, to=i)


def prop(i, data=b"somedata"):
    return msg(PROP, frm=i, to=i, entries=(Entry(data=data),))


# ---------------------------------------------------------------------------
# Elections
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("peers,expected_state", [
    ((None, None, None), StateType.LEADER),
    ((None, None, NOP_STEPPER), StateType.LEADER),
    ((None, NOP_STEPPER, NOP_STEPPER), StateType.CANDIDATE),
    ((None, NOP_STEPPER, NOP_STEPPER, None), StateType.CANDIDATE),
    ((None, NOP_STEPPER, NOP_STEPPER, None, None), StateType.LEADER),
])
def test_leader_election(peers, expected_state):
    nw = Network(*peers)
    nw.send(hup(1))
    sm = nw.peers[1]
    assert sm.state == expected_state
    assert sm.term == 1


def test_leader_election_overwrite_newer_logs():
    # Three-peer election with a candidate whose log lags: the up-to-date
    # peer's entries win (log matching / leader completeness).
    nw = Network(None, None, None)
    nw.send(hup(1))
    assert nw.peers[1].state == StateType.LEADER
    nw.send(prop(1))
    assert all(nw.peers[i].raft_log.committed == 2 for i in (1, 2, 3))


def test_single_node_candidate():
    nw = Network(None)
    nw.send(hup(1))
    assert nw.peers[1].state == StateType.LEADER


def test_dueling_candidates():
    a = new_test_raft(1, [1, 2, 3], 10, 1)
    b = new_test_raft(2, [1, 2, 3], 10, 1)
    c = new_test_raft(3, [1, 2, 3], 10, 1)
    nw = Network(a, b, c)
    nw.cut(1, 3)

    nw.send(hup(1))
    nw.send(hup(3))
    # 1 becomes leader since it receives votes from 1 and 2
    assert a.state == StateType.LEADER
    # 3 stays as candidate: it has been denied by both 1 (cut) and 2 (voted)
    assert c.state == StateType.CANDIDATE

    nw.recover()
    # Candidate 3 now increases its term and campaigns again; it disrupts the
    # leader (no prevote in this protocol version) but loses given its shorter
    # log, conceding to follower on majority rejection.
    nw.send(hup(3))

    wlog_committed = 1
    assert a.raft_log.committed == wlog_committed
    assert a.term == 2
    assert a.state == StateType.FOLLOWER
    assert c.term == 2
    assert c.state == StateType.FOLLOWER


def test_candidate_concede():
    nw = Network(None, None, None)
    nw.isolate(1)
    nw.send(hup(1))
    nw.send(hup(3))
    nw.recover()
    # Leader 3 sends a heartbeat + append; candidate 1 concedes.
    nw.send(msg(BEAT, frm=3, to=3))
    data = b"force follower"
    nw.send(msg(PROP, frm=3, to=3, entries=(Entry(data=data),)))

    a = nw.peers[1]
    assert a.state == StateType.FOLLOWER
    assert a.term == 1
    wanted = [Entry(term=1, index=1), Entry(term=1, index=2, data=data)]
    for i in (1, 2, 3):
        p = nw.peers[i]
        assert p.raft_log.committed == 2
        ents = p.raft_log.all_entries()
        assert [(e.term, e.index, e.data) for e in ents] == \
            [(e.term, e.index, e.data) for e in wanted]


def test_old_messages():
    nw = Network(None, None, None)
    nw.send(hup(1))
    nw.send(hup(2))
    nw.send(hup(1))
    # Pretend we're an old leader trying to make progress; this entry is
    # expected to be ignored.
    nw.send(msg(APP, frm=2, to=1, term=2, entries=(Entry(index=3, term=2),)))
    # Commit a new entry.
    nw.send(prop(1))

    l = nw.peers[1]
    ents = l.raft_log.all_entries()
    terms = [(e.term, e.index) for e in ents]
    assert terms == [(1, 1), (2, 2), (3, 3), (3, 4)]
    assert ents[-1].data == b"somedata"
    assert l.raft_log.committed == 4


# ---------------------------------------------------------------------------
# Proposals / replication
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("peers,success", [
    ((None, None, None), True),
    ((None, None, NOP_STEPPER), True),
    ((None, NOP_STEPPER, NOP_STEPPER), False),
    ((None, NOP_STEPPER, NOP_STEPPER, None), False),
    ((None, NOP_STEPPER, NOP_STEPPER, None, None), True),
])
def test_proposal(peers, success):
    nw = Network(*peers)
    nw.send(hup(1))
    nw.send(prop(1))

    want_log = [(1, 1), (1, 2)] if success else []
    for p in nw.peers.values():
        if not isinstance(p, Raft):
            continue
        got = [(e.term, e.index)
               for e in p.raft_log.all_entries()[:p.raft_log.committed]]
        assert got == want_log
    assert nw.peers[1].term == 1


def test_proposal_by_proxy():
    for peers in [(None, None, None), (None, None, NOP_STEPPER)]:
        nw = Network(*peers)
        nw.send(hup(1))
        # Propose via follower 2 — it forwards to leader 1.
        nw.send(prop(2))
        for p in nw.peers.values():
            if not isinstance(p, Raft):
                continue
            got = [(e.term, e.index)
                   for e in p.raft_log.all_entries()[:p.raft_log.committed]]
            assert got == [(1, 1), (1, 2)]
        assert nw.peers[1].term == 1


def test_log_replication():
    cases = [
        (Network(None, None, None), [prop(1)], 2),
        (Network(None, None, None), [prop(1), hup(2), prop(2)], 4),
    ]
    for nw, props, wcommitted in cases:
        nw.send(hup(1))
        for m in props:
            nw.send(m)
        for i, p in nw.peers.items():
            assert p.raft_log.committed == wcommitted
            ents = [e for e in next_ents(p, nw.storage[i]) if e.data]
            sent_props = [m.entries[0].data for m in props if m.type == PROP]
            assert [e.data for e in ents] == sent_props


def test_single_node_commit():
    nw = Network(None)
    nw.send(hup(1))
    nw.send(prop(1))
    nw.send(prop(1))
    assert nw.peers[1].raft_log.committed == 3


def test_cannot_commit_without_new_term_entry():
    # Entries from a previous term cannot be committed by counting replicas
    # alone (Raft paper §5.4.2).
    nw = Network(None, None, None, None, None)
    nw.send(hup(1))
    # network partition: 1 can no longer reach 3,4,5
    nw.cut(1, 3)
    nw.cut(1, 4)
    nw.cut(1, 5)
    nw.send(prop(1))
    nw.send(prop(1))
    sm = nw.peers[1]
    assert sm.raft_log.committed == 1

    nw.recover()
    # Avoid committing ChangeTerm proposals directly via heartbeats.
    nw.ignore(APP)
    nw.send(hup(2))
    sm2 = nw.peers[2]
    assert sm2.raft_log.committed == 1

    nw.recover()
    nw.send(msg(BEAT, frm=2, to=2))
    nw.send(msg(PROP, frm=2, to=2, entries=(Entry(data=b"x"),)))
    assert sm2.raft_log.committed == 5


def test_commit_without_new_term_entry():
    # ... but a new leader's own-term entry commits everything before it.
    nw = Network(None, None, None, None, None)
    nw.send(hup(1))
    nw.cut(1, 3)
    nw.cut(1, 4)
    nw.cut(1, 5)
    nw.send(prop(1))
    nw.send(prop(1))
    assert nw.peers[1].raft_log.committed == 1
    nw.recover()
    nw.send(hup(2))
    assert nw.peers[2].raft_log.committed == 4


# ---------------------------------------------------------------------------
# Commit computation (the quorum median — kernel's hot reduction)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("matches,log_terms,sm_term,w", [
    # single
    ([1], [(1, 1)], 1, 1),
    ([1], [(1, 1)], 2, 0),
    ([2], [(1, 1), (2, 2)], 2, 2),
    ([1], [(1, 2)], 2, 1),
    # odd
    ([2, 1, 1], [(1, 1), (2, 2)], 1, 1),
    ([2, 1, 1], [(1, 1), (2, 1)], 2, 0),
    ([2, 1, 2], [(1, 1), (2, 2)], 2, 2),
    ([2, 1, 2], [(1, 1), (2, 1)], 2, 0),
    # even
    ([2, 1, 1, 1], [(1, 1), (2, 2)], 1, 1),
    ([2, 1, 1, 1], [(1, 1), (2, 1)], 2, 0),
    ([2, 1, 1, 2], [(1, 1), (2, 2)], 1, 1),
    ([2, 1, 1, 2], [(1, 1), (2, 1)], 2, 0),
    ([2, 1, 2, 2], [(1, 1), (2, 2)], 2, 2),
    ([2, 1, 2, 2], [(1, 1), (2, 1)], 2, 0),
])
def test_commit(matches, log_terms, sm_term, w):
    storage = MemoryStorage()
    storage.append([Entry(index=i, term=t) for t, i in
                    [(t, i) for i, t in log_terms]])
    storage.set_hard_state(HardState(term=sm_term))

    r = new_test_raft(1, [1], 5, 1, storage)
    r.term = sm_term
    for j, m in enumerate(matches):
        r.set_progress(j + 1, m, m + 1)
    r.state = StateType.LEADER
    r.maybe_commit()
    assert r.raft_log.committed == w


def test_is_election_timeout_distribution():
    # elapsed just past the timeout should trigger ~ proportionally
    # (reference TestIsElectionTimeout); statistical bounds are loose.
    for elapse, wprob, round_trip in [
        (5, 0.0, False), (13, 0.3, True), (15, 0.5, True),
        (18, 0.8, True), (20, 1.0, False),
    ]:
        r = new_test_raft(1, [1], 10, 1)
        r.elapsed = elapse
        c = sum(1 for _ in range(10000) if r.is_election_timeout())
        got = c / 10000.0
        if round_trip:
            assert abs(got - wprob) < 0.3
        elif wprob == 0.0:
            assert got == 0.0
        else:
            assert got > 0.9


# ---------------------------------------------------------------------------
# Step edge cases
# ---------------------------------------------------------------------------

def test_step_ignore_old_term_msg():
    called = {"v": False}
    r = new_test_raft(1, [1], 10, 1)

    def fake_step(m):
        called["v"] = True

    r._step_fn = fake_step
    r.term = 2
    r.step(Message(type=APP, term=1))
    assert not called["v"]


@pytest.mark.parametrize("m,w_index,w_commit,w_reject", [
    # term mismatch at prev index -> reject
    (dict(term=2, log_term=3, index=2), 2, 0, True),
    (dict(term=2, log_term=3, index=3), 2, 0, True),
    # match
    (dict(term=2, log_term=1, index=1, commit=1), 2, 1, False),
    (dict(term=2, log_term=0, index=0, commit=1,
          entries=(Entry(index=1, term=2),)), 1, 1, False),
    (dict(term=2, log_term=2, index=2, commit=3,
          entries=(Entry(index=3, term=2), Entry(index=4, term=2))), 4, 3, False),
    (dict(term=2, log_term=2, index=2, commit=4,
          entries=(Entry(index=3, term=2),)), 3, 3, False),
    (dict(term=2, log_term=1, index=1, commit=4,
          entries=(Entry(index=2, term=2),)), 2, 2, False),
    # commit clamps
    (dict(term=2, log_term=2, index=2, commit=3), 2, 2, False),
    (dict(term=2, log_term=2, index=2, commit=4), 2, 2, False),
    (dict(term=2, log_term=2, index=2, commit=0), 2, 0, False),
])
def test_handle_msgapp(m, w_index, w_commit, w_reject):
    storage = MemoryStorage()
    storage.append([Entry(index=1, term=1), Entry(index=2, term=2)])
    r = new_test_raft(1, [1], 10, 1, storage)
    r.become_follower(2, raftpb.NO_LEADER)
    r.handle_append_entries(Message(type=APP, **m))
    assert r.raft_log.last_index() == w_index
    assert r.raft_log.committed == w_commit
    msgs = read_messages(r)
    assert len(msgs) == 1
    assert msgs[0].reject == w_reject


def test_handle_heartbeat():
    commit = 2
    for m_commit, w_commit in [(commit + 1, commit + 1), (commit - 1, commit)]:
        storage = MemoryStorage()
        storage.append([Entry(index=1, term=1), Entry(index=2, term=2),
                        Entry(index=3, term=3)])
        r = new_test_raft(1, [1, 2], 5, 1, storage)
        r.become_follower(2, 2)
        r.raft_log.commit_to(commit)
        r.handle_heartbeat(Message(type=HEARTBEAT, frm=2, to=1, term=2,
                                   commit=m_commit))
        assert r.raft_log.committed == w_commit
        msgs = read_messages(r)
        assert len(msgs) == 1
        assert msgs[0].type == HEARTBEAT_RESP


def test_handle_heartbeat_resp():
    # Leader re-sends append when follower's match lags after heartbeat resp.
    storage = MemoryStorage()
    storage.append([Entry(index=1, term=1), Entry(index=2, term=2),
                    Entry(index=3, term=3)])
    r = new_test_raft(1, [1, 2], 5, 1, storage)
    r.become_candidate()
    r.become_leader()
    r.raft_log.commit_to(r.raft_log.last_index())

    r.step(Message(type=HEARTBEAT_RESP, frm=2, term=r.term))
    msgs = read_messages(r)
    assert len(msgs) == 1
    assert msgs[0].type == APP

    # Once the follower is caught up, no more appends on heartbeat resp.
    r.step(Message(type=APP_RESP, frm=2, term=r.term,
                   index=msgs[0].index + len(msgs[0].entries)))
    read_messages(r)
    r.step(Message(type=HEARTBEAT_RESP, frm=2, term=r.term))
    assert read_messages(r) == []


@pytest.mark.parametrize("state,i,term,vote_for,w_reject", [
    (StateType.FOLLOWER, 0, 0, raftpb.NO_LEADER, True),
    (StateType.FOLLOWER, 0, 1, raftpb.NO_LEADER, True),
    (StateType.FOLLOWER, 0, 2, raftpb.NO_LEADER, True),
    (StateType.FOLLOWER, 0, 3, raftpb.NO_LEADER, False),
    (StateType.FOLLOWER, 1, 0, raftpb.NO_LEADER, True),
    (StateType.FOLLOWER, 1, 1, raftpb.NO_LEADER, True),
    (StateType.FOLLOWER, 1, 2, raftpb.NO_LEADER, True),
    (StateType.FOLLOWER, 1, 3, raftpb.NO_LEADER, False),
    (StateType.FOLLOWER, 2, 0, raftpb.NO_LEADER, True),
    (StateType.FOLLOWER, 2, 1, raftpb.NO_LEADER, True),
    (StateType.FOLLOWER, 2, 2, raftpb.NO_LEADER, False),
    (StateType.FOLLOWER, 2, 3, raftpb.NO_LEADER, False),
    (StateType.FOLLOWER, 3, 0, raftpb.NO_LEADER, True),
    (StateType.FOLLOWER, 3, 1, raftpb.NO_LEADER, True),
    (StateType.FOLLOWER, 3, 2, raftpb.NO_LEADER, False),
    (StateType.FOLLOWER, 3, 3, raftpb.NO_LEADER, False),
    (StateType.FOLLOWER, 3, 2, 2, False),
    (StateType.FOLLOWER, 3, 2, 1, True),
    (StateType.LEADER, 3, 3, 1, True),
    (StateType.CANDIDATE, 3, 3, 1, True),
])
def test_recv_msgvote(state, i, term, vote_for, w_reject):
    r = new_test_raft(1, [1], 10, 1)
    r.state = state
    r._step_fn = {StateType.FOLLOWER: r._step_follower,
                  StateType.CANDIDATE: r._step_candidate,
                  StateType.LEADER: r._step_leader}[state]
    r.vote = vote_for
    storage = r.raft_log.storage
    storage.append([Entry(index=1, term=2), Entry(index=2, term=2)])
    r.raft_log = type(r.raft_log)(storage)

    r.step(Message(type=VOTE, frm=2, index=i, log_term=term))
    msgs = read_messages(r)
    assert len(msgs) == 1
    assert msgs[0].type == VOTE_RESP
    assert msgs[0].reject == w_reject


@pytest.mark.parametrize("from_state,to_state,wallow,wterm,wlead", [
    (StateType.FOLLOWER, StateType.FOLLOWER, True, 1, raftpb.NO_LEADER),
    (StateType.FOLLOWER, StateType.CANDIDATE, True, 1, raftpb.NO_LEADER),
    (StateType.FOLLOWER, StateType.LEADER, False, 0, raftpb.NO_LEADER),
    (StateType.CANDIDATE, StateType.FOLLOWER, True, 0, raftpb.NO_LEADER),
    (StateType.CANDIDATE, StateType.CANDIDATE, True, 1, raftpb.NO_LEADER),
    (StateType.CANDIDATE, StateType.LEADER, True, 0, 1),
    (StateType.LEADER, StateType.FOLLOWER, True, 1, raftpb.NO_LEADER),
    (StateType.LEADER, StateType.CANDIDATE, False, 1, raftpb.NO_LEADER),
    (StateType.LEADER, StateType.LEADER, True, 0, 1),
])
def test_state_transition(from_state, to_state, wallow, wterm, wlead):
    r = new_test_raft(1, [1], 10, 1)
    r.state = from_state
    if from_state == StateType.LEADER:
        # becomeLeader requires prs self-match bookkeeping; set minimal state.
        r.prs[1].match = r.raft_log.last_index()

    def do():
        if to_state == StateType.FOLLOWER:
            r.become_follower(wterm, wlead)
        elif to_state == StateType.CANDIDATE:
            r.become_candidate()
        else:
            r.become_leader()

    if not wallow:
        with pytest.raises(RuntimeError):
            do()
    else:
        do()
        assert r.term == wterm
        assert r.lead == wlead


def test_all_server_stepdown():
    cases = [
        (StateType.FOLLOWER, StateType.FOLLOWER, 3, 0),
        (StateType.CANDIDATE, StateType.FOLLOWER, 3, 0),
        (StateType.LEADER, StateType.FOLLOWER, 3, 1),
    ]
    tmsg_types = [VOTE, APP]
    tterm = 3
    for state, wstate, wterm, windex in cases:
        r = new_test_raft(1, [1, 2, 3], 10, 1)
        if state == StateType.CANDIDATE:
            r.become_candidate()
        elif state == StateType.LEADER:
            r.become_candidate()
            r.become_leader()

        for mt in tmsg_types:
            r.step(Message(type=mt, frm=2, term=tterm, log_term=tterm))
            assert r.state == wstate
            assert r.term == wterm
            assert r.raft_log.last_index() == windex
            assert len(r.raft_log.all_entries()) == windex
            wlead = 2 if mt == APP else raftpb.NO_LEADER
            assert r.lead == wlead


def test_leader_app_resp():
    # (index, reject, match, next, #msgs, window_index, window_commit)
    cases = [
        (3, True, 0, 3, 0, 0, 0),    # stale resp: no replies
        (2, True, 0, 2, 1, 1, 0),    # denied resp: decrease next, send probe
        (2, False, 2, 4, 2, 2, 2),   # accepted: commit and broadcast
        (0, False, 0, 3, 0, 0, 0),   # ignore heartbeat-style resp
    ]
    for index, reject, wmatch, wnext, wmsg_num, windex, wcommit in cases:
        storage = MemoryStorage()
        storage.append([Entry(index=1, term=0), Entry(index=2, term=1)])
        r = new_test_raft(1, [1, 2, 3], 10, 1, storage)
        r.raft_log = type(r.raft_log)(storage)
        r.become_candidate()
        r.become_leader()
        read_messages(r)
        r.step(Message(type=APP_RESP, frm=2, term=r.term, index=index,
                       reject=reject, reject_hint=index))
        p = r.prs[2]
        assert p.match == wmatch
        assert p.next == wnext
        msgs = read_messages(r)
        assert len(msgs) == wmsg_num
        for m in msgs:
            assert m.index == windex
            assert m.commit == wcommit


def test_bcast_beat():
    # Leader heartbeats attach commit = min(follower.match, committed).
    offset = 1000
    s = Snapshot(metadata=SnapshotMetadata(
        index=offset, term=1, conf_state=ConfState(nodes=(1, 2, 3))))
    storage = MemoryStorage(snapshot=s)
    r = new_test_raft(1, [], 10, 1, storage)
    r.term = 1
    r.become_candidate()
    r.become_leader()
    for i in range(10):
        r.append_entry(Entry(index=i + 1))
    r.prs[2].match, r.prs[2].next = 5, 6
    r.prs[3].match, r.prs[3].next = offset + 10, offset + 11
    read_messages(r)
    r.step(Message(type=BEAT, frm=1))
    msgs = read_messages(r)
    assert len(msgs) == 2
    want_commits = {2: min(5, r.raft_log.committed),
                    3: min(offset + 10, r.raft_log.committed)}
    for m in msgs:
        assert m.type == HEARTBEAT
        assert m.index == 0
        assert m.log_term == 0
        assert m.commit == want_commits[m.to]
        assert not m.entries


def test_recv_msgbeat():
    cases = [(StateType.LEADER, 2), (StateType.CANDIDATE, 0),
             (StateType.FOLLOWER, 0)]
    for state, w_msg in cases:
        storage = MemoryStorage()
        storage.append([Entry(index=1, term=0), Entry(index=2, term=1)])
        r = new_test_raft(1, [1, 2, 3], 10, 1, storage)
        r.raft_log = type(r.raft_log)(storage)
        r.term = 1
        r.state = state
        r._step_fn = {StateType.FOLLOWER: r._step_follower,
                      StateType.CANDIDATE: r._step_candidate,
                      StateType.LEADER: r._step_leader}[state]
        r.step(Message(type=BEAT, frm=1))
        msgs = read_messages(r)
        assert len(msgs) == w_msg
        for m in msgs:
            assert m.type == HEARTBEAT


def test_leader_increase_next():
    prev_ents = [Entry(term=1, index=1), Entry(term=1, index=2),
                 Entry(term=1, index=3)]
    cases = [
        # replicate state: optimistic next = prev entries + noop + propose + 1
        (ProgressState.REPLICATE, 2, len(prev_ents) + 2 + 1),
        # probe state: not advanced
        (ProgressState.PROBE, 2, 2),
    ]
    for state, next_idx, wnext in cases:
        r = new_test_raft(1, [1, 2], 10, 1)
        r.raft_log.append(prev_ents)
        r.become_candidate()
        r.become_leader()
        r.prs[2].state = state
        r.prs[2].next = next_idx
        r.step(prop(1).type and Message(type=PROP, frm=1,
                                        entries=(Entry(data=b"d"),)))
        assert r.prs[2].next == wnext


# ---------------------------------------------------------------------------
# Snapshot install / restore
# ---------------------------------------------------------------------------

def make_snapshot(index=11, term=11, nodes=(1, 2)):
    return Snapshot(metadata=SnapshotMetadata(
        index=index, term=term, conf_state=ConfState(nodes=tuple(nodes))))


def test_restore():
    s = make_snapshot(11, 11, (1, 2, 3))
    r = new_test_raft(1, [1, 2], 10, 1)
    assert r.restore(s)
    assert r.raft_log.last_index() == s.metadata.index
    assert r.raft_log.term_or_zero(s.metadata.index) == s.metadata.term
    assert sorted(r.nodes()) == [1, 2, 3]
    assert not r.restore(s)


def test_restore_ignore_snapshot():
    prev_ents = [Entry(term=1, index=1), Entry(term=1, index=2),
                 Entry(term=1, index=3)]
    commit = 1
    r = new_test_raft(1, [1, 2], 10, 1)
    r.raft_log.append(prev_ents)
    r.raft_log.commit_to(commit)
    s = make_snapshot(commit, 1, (1, 2))
    # Ignore snapshot at/below committed.
    assert not r.restore(s)
    assert r.raft_log.committed == commit
    # Fast-forward commit when log already matches.
    s2 = make_snapshot(commit + 1, 1, (1, 2))
    assert not r.restore(s2)
    assert r.raft_log.committed == commit + 1


def test_provide_snap():
    s = make_snapshot(11, 11, (1, 2))
    storage = MemoryStorage()
    r = new_test_raft(1, [1], 10, 1, storage)
    r.restore(s)
    r.become_candidate()
    r.become_leader()
    # Force peer 2 behind the first index: leader must send a snapshot.
    r.prs[2].next = r.raft_log.first_index() - 1
    r.prs[2].resume()
    r.step(Message(type=PROP, frm=1, entries=(Entry(data=b"somedata"),)))
    msgs = read_messages(r)
    assert len(msgs) == 1
    assert msgs[0].type == SNAP


def test_restore_from_snap_msg():
    s = make_snapshot(11, 11, (1, 2))
    m = Message(type=SNAP, frm=1, term=2, snapshot=s)
    r = new_test_raft(2, [1, 2], 10, 1)
    r.step(m)
    assert r.raft_log.last_index() == s.metadata.index


def test_slow_node_restore():
    nw = Network(None, None, None)
    nw.send(hup(1))
    nw.isolate(3)
    for _ in range(101):
        nw.send(prop(1))
    lead = nw.peers[1]
    # Persist + compact the leader's log behind a snapshot.
    next_ents(lead, nw.storage[1])
    nw.storage[1].create_snapshot(
        lead.raft_log.applied, ConfState(nodes=tuple(lead.nodes())), b"")
    nw.storage[1].compact(lead.raft_log.applied)

    nw.recover()
    # Send heartbeats until the slow follower 3 reports back; leader then
    # ships the snapshot.
    while True:
        nw.send(msg(BEAT, frm=1, to=1))
        if lead.prs[3].state != ProgressState.SNAPSHOT:
            break
    # Trigger a new proposal so follower 3 fully catches up.
    nw.send(prop(1))
    follower = nw.peers[3]
    assert follower.raft_log.committed == lead.raft_log.committed


# ---------------------------------------------------------------------------
# Membership changes
# ---------------------------------------------------------------------------

def test_step_config():
    r = new_test_raft(1, [1, 2], 10, 1)
    r.become_candidate()
    r.become_leader()
    index = r.raft_log.last_index()
    r.step(Message(type=PROP, frm=1,
                   entries=(Entry(type=EntryType.CONF_CHANGE),)))
    assert r.raft_log.last_index() == index + 1
    assert r.pending_conf


def test_step_ignore_config():
    # Second conf-change proposal while one is pending is demoted to a no-op.
    r = new_test_raft(1, [1, 2], 10, 1)
    r.become_candidate()
    r.become_leader()
    r.step(Message(type=PROP, frm=1,
                   entries=(Entry(type=EntryType.CONF_CHANGE),)))
    index = r.raft_log.last_index()
    pending = r.pending_conf
    r.step(Message(type=PROP, frm=1,
                   entries=(Entry(type=EntryType.CONF_CHANGE),)))
    wents = [Entry(type=EntryType.NORMAL, term=1, index=3)]
    ents = r.raft_log.entries(index + 1)
    assert [(e.type, e.term, e.index, e.data) for e in ents] == \
        [(e.type, e.term, e.index, e.data) for e in wents]
    assert r.pending_conf == pending


def test_recover_pending_config():
    for ent_type, wpending in [(EntryType.NORMAL, False),
                               (EntryType.CONF_CHANGE, True)]:
        r = new_test_raft(1, [1, 2], 10, 1)
        r.append_entry(Entry(type=ent_type))
        r.become_candidate()
        r.become_leader()
        assert r.pending_conf == wpending


def test_recover_double_pending_config():
    r = new_test_raft(1, [1, 2], 10, 1)
    r.append_entry(Entry(type=EntryType.CONF_CHANGE))
    r.append_entry(Entry(type=EntryType.CONF_CHANGE))
    r.become_candidate()
    with pytest.raises(RuntimeError):
        r.become_leader()


def test_add_node():
    r = new_test_raft(1, [1], 10, 1)
    r.pending_conf = True
    r.add_node(2)
    assert not r.pending_conf
    assert sorted(r.nodes()) == [1, 2]


def test_remove_node():
    r = new_test_raft(1, [1, 2], 10, 1)
    r.remove_node(2)
    assert not r.pending_conf
    assert r.nodes() == [1]
    # Removing all nodes is allowed at this layer.
    r.remove_node(1)
    assert r.nodes() == []


def test_promotable():
    assert new_test_raft(1, [1], 5, 1).promotable()
    assert new_test_raft(1, [1, 2, 3], 5, 1).promotable()
    assert not new_test_raft(1, [2, 3], 5, 1).promotable()


def test_campaign_while_leader():
    r = new_test_raft(1, [1], 5, 1)
    assert r.state == StateType.FOLLOWER
    r.step(Message(type=HUP, frm=1))
    assert r.state == StateType.LEADER
    term = r.term
    r.step(Message(type=HUP, frm=1))
    assert r.state == StateType.LEADER
    assert r.term == term


def test_commit_after_remove_node():
    # Pending commands can become committed when a node is removed.
    storage = MemoryStorage()
    r = new_test_raft(1, [1, 2], 5, 1, storage)
    r.become_candidate()
    r.become_leader()

    # Begin to remove node 2.
    cc = ConfChange(type=ConfChangeType.REMOVE_NODE, node_id=2)
    r.step(Message(type=PROP, frm=1, entries=(
        Entry(type=EntryType.CONF_CHANGE, data=raftpb.encode_conf_change(cc)),)))
    # Stabilize the log and make sure nothing is committed yet.
    assert not next_ents(r, storage)
    cc_index = r.raft_log.last_index()

    # A normal proposal while the config change is pending.
    r.step(Message(type=PROP, frm=1, entries=(Entry(data=b"hello"),)))
    # Node 2 acknowledges the config change, committing it.
    r.step(Message(type=APP_RESP, frm=2, term=r.term, index=cc_index))
    ents = next_ents(r, storage)
    assert len(ents) == 2
    assert ents[0].type == EntryType.NORMAL and not ents[0].data
    assert ents[1].type == EntryType.CONF_CHANGE

    # Apply the config change; the pending command can now commit.
    r.remove_node(2)
    ents = next_ents(r, storage)
    assert len(ents) == 1
    assert ents[0].type == EntryType.NORMAL
    assert ents[0].data == b"hello"
