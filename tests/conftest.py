"""Test environment: force JAX onto a virtual 8-device CPU mesh so sharding
paths compile/execute without TPU hardware (the driver separately dry-runs the
multi-chip path; see __graft_entry__.py).

IMPORTANT: this image preloads jax at interpreter start (axon site hook), so
setting JAX_PLATFORMS in os.environ here is too late — the already-imported
jax captured the ambient "axon" platform config, whose backend init dials a
TPU tunnel that can hang. Force the platform through jax.config.update, which
works any time before the first backend is instantiated. XLA_FLAGS is still
read lazily at CPU-client creation, so the env route works for the device
count.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"   # for any subprocesses tests spawn
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402  (preloaded anyway — see module docstring)

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running chaos/e2e test")
