"""Test environment: force JAX onto a virtual 8-device CPU mesh so sharding
paths compile/execute without TPU hardware (the driver separately dry-runs the
multi-chip path; see __graft_entry__.py). The forcing logic — robust against
this image's jax preload (axon site hook) — is shared with bench.py and
__graft_entry__.py via etcd_tpu.utils.platform."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_ENABLE_X64", "1")
# Keep the bcrypt-stand-in cheap under test (production default is 600k;
# the count is tagged into each hash, so both verify correctly).
os.environ.setdefault("ETCD_PBKDF2_ITERS", "4096")

from etcd_tpu.utils.platform import enable_compile_cache, force_cpu  # noqa: E402

force_cpu(8)
enable_compile_cache()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running chaos/e2e test")
