"""Test environment: force JAX onto a virtual 8-device CPU mesh so sharding
paths compile/execute without TPU hardware (the driver separately dry-runs the
multi-chip path; see __graft_entry__.py)."""
import os

# Force CPU regardless of ambient env: the axon TPU backend is tunneled,
# slow to init, and not what unit tests should exercise.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
