"""The quiescent fast path (kernel.step_routed_auto) must be TRAJECTORY-
IDENTICAL to the full kernel: its on-device predicate may only select the
one-pass message phase when that phase is bit-exact with the P sequential
passes, so stepping the same schedule through both functions — elections,
proposals, partitions, re-elections — must agree on every state field and
the routed inbox after every round.
"""
import numpy as np

import jax.numpy as jnp

from etcd_tpu.ops import kernel
from etcd_tpu.ops.state import LEADER, KernelConfig, init_state


def _fields(st):
    return {k: np.asarray(v) for k, v in st._asdict().items()}


def _assert_same(sa, sb, ia, ib, r):
    fa, fb = _fields(sa), _fields(sb)
    for k in fa:
        assert np.array_equal(fa[k], fb[k]), \
            f"round {r}: field {k} diverged\n{fa[k]}\n{fb[k]}"
    assert np.array_equal(np.asarray(ia), np.asarray(ib)), \
        f"round {r}: inbox diverged"


def test_auto_matches_full_trajectory():
    G, P = 8, 5
    cfg = KernelConfig(groups=G, peers=P, window=8, max_ents=2,
                       election_tick=10, heartbeat_tick=3)
    rng = np.random.default_rng(7)

    st_f = init_state(cfg, stagger=True)
    st_a = init_state(cfg, stagger=True)
    # Separate buffers: the stepping functions donate their inputs.
    in_f = jnp.zeros((G, P, P, cfg.fields), jnp.int32)
    in_a = jnp.zeros((G, P, P, cfg.fields), jnp.int32)
    zero = jnp.zeros(G, jnp.int32)

    quiet_rounds = 0
    drop = None
    for r in range(260):
        # Mid-run chaos: partition group 3's leader for 25 rounds to force
        # a re-election (auto must fall back to the full path), then heal.
        if r == 120 or r == 145:
            state = np.asarray(st_f.state)
            lead3 = int((state[3] == LEADER).argmax())
            m_to = np.ones((G, P, 1, 1), np.int32)
            m_from = np.ones((G, 1, P, 1), np.int32)
            if r == 120:
                m_to[3, lead3] = 0
                m_from[3, 0, lead3] = 0
                drop = jnp.asarray(m_to * m_from)
            else:
                drop = None

        # Proposals at the full-state's current leaders (identical states
        # => identical slots).
        state = np.asarray(st_f.state)
        has_lead = (state == LEADER).any(axis=1)
        slots = jnp.asarray((state == LEADER).argmax(axis=1)
                            .astype(np.int32))
        pc = jnp.asarray(
            (rng.integers(0, cfg.max_ents + 1, size=G)
             * has_lead).astype(np.int32)) if r % 3 else zero

        quiet_rounds += bool(kernel._quiet_pred(
            st_f, cfg, in_f, st_f.peer_mask, jnp.asarray(True)))

        st_f, in_f = kernel.step_routed(cfg, st_f, in_f, pc, slots,
                                        jnp.asarray(True))
        st_a, in_a = kernel.step_routed_auto(cfg, st_a, in_a, pc, slots,
                                             jnp.asarray(True))
        if drop is not None:
            in_f = in_f * drop
            in_a = in_a * drop
        _assert_same(st_f, st_a, in_f, in_a, r)

    commit = np.asarray(st_f.commit)
    assert (commit.max(axis=1) > 10).all(), commit
    # The fast path must actually have engaged (and not always).
    assert quiet_rounds > 100, quiet_rounds
    assert quiet_rounds < 260, quiet_rounds


def test_multihop_equals_chained_single_hops():
    """hops=H must be bit-identical to H successive 1-hop invocations
    whose last H-1 carry no proposals and no tick — including under a
    drop mask, which the multi-hop kernel applies after every internal
    routing (the fault-injection contract)."""
    G, P, H = 6, 5, 3
    cfg = KernelConfig(groups=G, peers=P, window=8, max_ents=2,
                       election_tick=10, heartbeat_tick=3)
    rng = np.random.default_rng(11)

    st_m = init_state(cfg, stagger=True)
    st_s = init_state(cfg, stagger=True)
    in_m = jnp.zeros((G, P, P, cfg.fields), jnp.int32)
    in_s = jnp.zeros((G, P, P, cfg.fields), jnp.int32)
    zero = jnp.zeros(G, jnp.int32)
    false = jnp.asarray(False)

    drop = None
    for r in range(80):
        if r == 30:
            # Partition group 2's slot 1 (both directions).
            m_to = np.ones((G, P, 1, 1), np.int32)
            m_from = np.ones((G, 1, P, 1), np.int32)
            m_to[2, 1] = 0
            m_from[2, 0, 1] = 0
            drop = jnp.asarray(m_to * m_from)
        if r == 55:
            drop = None

        state = np.asarray(st_s.state)
        has_lead = (state == LEADER).any(axis=1)
        slots = jnp.asarray((state == LEADER).argmax(axis=1)
                            .astype(np.int32))
        pc = jnp.asarray(
            (rng.integers(0, cfg.max_ents + 1, size=G)
             * has_lead).astype(np.int32)) if r % 2 else zero

        st_m, in_m = kernel.step_routed_auto(cfg, st_m, in_m, pc, slots,
                                             jnp.asarray(True), drop, H)
        for h in range(H):
            st_s, in_s = kernel.step_routed_auto(
                cfg, st_s, in_s, pc if h == 0 else zero, slots,
                jnp.asarray(True) if h == 0 else false)
            if drop is not None:
                in_s = in_s * drop
        _assert_same(st_m, st_s, in_m, in_s, r)

    commit = np.asarray(st_m.commit)
    assert (commit.max(axis=1) > 10).all(), commit


def test_multihop_commits_proposal_in_one_round():
    """With hops=3 a proposal staged at an established leader must be
    COMMITTED by the same invocation's readback (the ack-latency
    contract the engine's cfg.hops relies on)."""
    G, P = 4, 5
    cfg = KernelConfig(groups=G, peers=P, window=8, max_ents=2,
                       election_tick=10, heartbeat_tick=3)
    st = init_state(cfg, stagger=True)
    inbox = jnp.zeros((G, P, P, cfg.fields), jnp.int32)
    zero = jnp.zeros(G, jnp.int32)
    # Let elections settle (multi-hop: one round does the whole exchange).
    for _ in range(6):
        st, inbox = kernel.step_routed_auto(cfg, st, inbox, zero, zero,
                                            jnp.asarray(True), None, 3)
    state = np.asarray(st.state)
    assert ((state == LEADER).sum(axis=1) == 1).all()
    slots = jnp.asarray((state == LEADER).argmax(axis=1).astype(np.int32))
    commit0 = np.asarray(st.commit).max(axis=1)
    st, inbox = kernel.step_routed_auto(cfg, st, inbox,
                                        jnp.full(G, 2, jnp.int32), slots,
                                        jnp.asarray(True), None, 3)
    commit1 = np.asarray(st.commit).max(axis=1)
    assert (commit1 >= commit0 + 2).all(), (commit0, commit1)


def test_slots_auto_matches_full_slots_kernel():
    """The multi-host step's auto+multi-hop variant must be trajectory-
    identical to the always-full step_routed_slots chained hop by hop
    (per-slot proposals + tick on hop 0 only)."""
    G, P, H = 6, 3, 3
    cfg = KernelConfig(groups=G, peers=P, window=8, max_ents=2,
                       election_tick=10, heartbeat_tick=3)
    rng = np.random.default_rng(13)

    st_a = init_state(cfg, stagger=True)
    st_f = init_state(cfg, stagger=True)
    in_a = jnp.zeros((G, P, P, cfg.fields), jnp.int32)
    in_f = jnp.zeros((G, P, P, cfg.fields), jnp.int32)
    zero_gp = jnp.zeros((G, P), jnp.int32)
    false = jnp.asarray(False)

    for r in range(60):
        state = np.asarray(st_f.state)
        cnt = np.zeros((G, P), np.int32)
        lead = (state == LEADER)
        cnt[lead] = rng.integers(0, cfg.max_ents + 1,
                                 size=int(lead.sum()))
        cnt_j = jnp.asarray(cnt)

        st_a, in_a = kernel.step_routed_slots_auto(
            cfg, st_a, in_a, cnt_j, jnp.asarray(True), None, H)
        for h in range(H):
            st_f, in_f = kernel.step_routed_slots(
                cfg, st_f, in_f, cnt_j if h == 0 else zero_gp,
                jnp.asarray(True) if h == 0 else false)
        _assert_same(st_a, st_f, in_a, in_f, r)

    commit = np.asarray(st_a.commit)
    assert (commit.max(axis=1) > 5).all(), commit
