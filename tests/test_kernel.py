"""Batched-kernel protocol tests (tier-1 for the TPU path): elections,
replication and commit through the dense (G, P) step; safety invariants under
random message loss; bit-exact election-timing equivalence with the scalar
oracle."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from etcd_tpu.ops import kernel
from etcd_tpu.ops.state import (CANDIDATE, FOLLOWER, LEADER, GroupState,
                                KernelConfig, M_NONE, init_state)
from etcd_tpu.raft.core import Config as ScalarConfig, Raft
from etcd_tpu.raft.storage import MemoryStorage


def make(groups=4, peers=3, **kw):
    cfg = KernelConfig(groups=groups, peers=peers, **kw)
    return cfg, init_state(cfg)


def empty_inbox(cfg):
    return jnp.zeros((cfg.groups, cfg.peers, cfg.peers, cfg.fields),
                     jnp.int32)


def leader_slot(st):
    """(G,) leader slot per group, -1 if none."""
    is_l = np.asarray(st.state == LEADER)
    has = is_l.any(axis=1)
    return np.where(has, is_l.argmax(axis=1), -1)


def run_rounds(cfg, st, rounds, inbox=None, props=None, drop=None,
               tick=True):
    if inbox is None:
        inbox = empty_inbox(cfg)
    zero_props = jnp.zeros(cfg.groups, jnp.int32)
    for r in range(rounds):
        if props is not None:
            pc, ps = props(r, st)
        else:
            pc, ps = zero_props, zero_props
        st, outbox = kernel.step(cfg, st, inbox, pc, ps,
                                 jnp.asarray(tick))
        inbox = kernel.route_local(outbox)
        if drop is not None:
            inbox = drop(r, inbox)
    return st, inbox


def test_election_happens_everywhere():
    cfg, st = make(groups=8, peers=3)
    st, _ = run_rounds(cfg, st, 60)
    slots = leader_slot(st)
    assert (slots >= 0).all(), f"groups without leader: {np.where(slots < 0)}"
    # Exactly one leader per group, and every peer agrees on the leader.
    n_leaders = np.asarray((st.state == LEADER)).sum(axis=1)
    assert (n_leaders == 1).all()
    lead = np.asarray(st.lead)
    for g in range(cfg.groups):
        ldr = slots[g] + 1
        assert set(lead[g]) == {ldr}, (g, lead[g])


def test_noop_commits_in_quiescent_group():
    # A new leader must replicate + commit its own-term no-op entry with NO
    # client proposals (Raft paper §5.4.2); regression for the off-by-one
    # where follower `next` skipped the no-op and quiescent groups never
    # committed anything.
    cfg, st = make(groups=4, peers=3)
    st, _ = run_rounds(cfg, st, 120)
    commit = np.asarray(st.commit)
    last = np.asarray(st.last_index)
    assert (commit >= 1).all(), commit
    assert (last >= 1).all(), last


def test_single_peer_group_instant_leader():
    cfg, st = make(groups=2, peers=3)
    st = st._replace(peer_mask=jnp.array([[True, False, False],
                                          [True, True, True]]))
    st, _ = run_rounds(cfg, st, 25)
    assert np.asarray(st.state)[0, 0] == LEADER
    # Inactive slots never move.
    assert (np.asarray(st.state)[0, 1:] == FOLLOWER).all()
    assert (np.asarray(st.term)[0, 1:] == 0).all()


def test_proposals_commit_and_replicate():
    cfg, st = make(groups=4, peers=3)
    st, inbox = run_rounds(cfg, st, 60)
    slots = leader_slot(st)
    assert (slots >= 0).all()
    base_commit = np.asarray(st.commit)[np.arange(cfg.groups), slots].copy()

    def props(r, cur):
        if r == 0:
            return (jnp.full(cfg.groups, 2, jnp.int32),
                    jnp.asarray(slots, jnp.int32))
        return jnp.zeros(cfg.groups, jnp.int32), jnp.zeros(cfg.groups, jnp.int32)

    st, _ = run_rounds(cfg, st, 6, inbox=inbox, props=props, tick=False)
    commit = np.asarray(st.commit)
    for g in range(cfg.groups):
        # Leader committed the 2 new entries...
        assert commit[g, slots[g]] >= base_commit[g] + 2, (
            g, commit[g], base_commit[g])
        # ...and followers converged too (commit rides appends/heartbeats —
        # allow them to lag the leader by the entries not yet re-advertised).
        for p in range(cfg.peers):
            assert np.asarray(st.last_index)[g, p] >= base_commit[g] + 2


def test_commit_propagates_to_followers_via_heartbeat():
    cfg, st = make(groups=2, peers=3)
    st, inbox = run_rounds(cfg, st, 60)
    slots = leader_slot(st)

    def props(r, cur):
        if r == 0:
            return (jnp.ones(cfg.groups, jnp.int32),
                    jnp.asarray(slots, jnp.int32))
        return jnp.zeros(cfg.groups, jnp.int32), jnp.zeros(cfg.groups, jnp.int32)

    # Keep ticking so heartbeats fire and carry the commit index.
    st, _ = run_rounds(cfg, st, 10, inbox=inbox, props=props, tick=True)
    commit = np.asarray(st.commit)
    lead_commit = commit[np.arange(cfg.groups), slots]
    for g in range(cfg.groups):
        for p in range(cfg.peers):
            assert commit[g, p] == lead_commit[g], (g, p, commit[g])


def test_staggered_init_elects_in_three_rounds():
    cfg = KernelConfig(groups=16, peers=5)
    st = init_state(cfg, stagger=True)
    st, _ = run_rounds(cfg, st, 3)
    n_leaders = np.asarray((st.state == LEADER)).sum(axis=1)
    assert (n_leaders == 1).all()
    # the staggered slot g % P is the winner
    slots = leader_slot(st)
    assert (slots == np.arange(16) % 5).all()


def test_flow_window_pauses_replication_to_partitioned_follower():
    """A silent follower must stop receiving appends once
    effective_flow_window (window//2) entries are un-acked — BEFORE its
    needed entries fall off the device ring (reference inflights semantics,
    progress.go:172-237, re-expressed as entries-in-flight)."""
    cfg = KernelConfig(groups=2, peers=3, window=8, max_ents=2)
    assert cfg.effective_flow_window == 4
    st = init_state(cfg, stagger=True)
    # Elect, then run a few live rounds so every follower acks at least once
    # and the leader's progress reaches REPLICATE (a never-acked follower
    # stays in PROBE, which paces at one probe per heartbeat instead).
    st, inbox = run_rounds(cfg, st, 6)
    slots = leader_slot(st)
    assert (slots >= 0).all()
    g = np.arange(cfg.groups)
    dead = (slots + 1) % cfg.peers  # partition one non-leader slot
    from etcd_tpu.ops.state import PR_REPLICATE
    assert (np.asarray(st.pr_state)[g, slots, dead] == PR_REPLICATE).all()

    def drop(r, inbox):
        arr = np.array(inbox)   # writable copy
        g = np.arange(cfg.groups)
        arr[g, dead] = 0        # nothing delivered TO the dead slot
        arr[g, :, dead] = 0     # nothing delivered FROM it
        return jnp.asarray(arr)

    def props(r, cur):
        return (jnp.full(cfg.groups, cfg.max_ents, jnp.int32),
                jnp.asarray(slots, jnp.int32))

    st, _ = run_rounds(cfg, st, 12, inbox=inbox, props=props, drop=drop)
    nxt = np.asarray(st.next)[g, slots, dead]
    match = np.asarray(st.match)[g, slots, dead]
    unacked = nxt - 1 - match
    # in-flight to the dead follower capped exactly at the flow window
    assert (unacked <= cfg.effective_flow_window).all(), unacked
    assert (unacked == cfg.effective_flow_window).all(), (
        "pause engaged early", unacked)
    # the live majority kept committing meanwhile
    commit = np.asarray(st.commit)[g, slots]
    assert (commit >= 10).all(), commit


def test_leader_unique_per_term_under_chaos():
    cfg, st = make(groups=6, peers=5)
    rng = np.random.RandomState(7)
    leaders_by_term = {}  # (g, term) -> slot

    def drop(r, inbox):
        mask = rng.rand(cfg.groups, cfg.peers, cfg.peers) < 0.3
        return jnp.where(jnp.asarray(mask)[..., None], 0, inbox)

    inbox = None
    for chunk in range(30):
        st, inbox = run_rounds(cfg, st, 5, inbox=inbox, drop=drop)
        state = np.asarray(st.state)
        term = np.asarray(st.term)
        for g in range(cfg.groups):
            for p in range(cfg.peers):
                if state[g, p] == LEADER:
                    key = (g, term[g, p])
                    assert leaders_by_term.setdefault(key, p) == p, (
                        f"two leaders in group {g} term {term[g, p]}")


def test_committed_prefix_never_changes_under_chaos():
    cfg, st = make(groups=4, peers=3, window=16, max_ents=2)
    rng = np.random.RandomState(11)
    # (g, index) -> term of committed entry as first observed
    committed = {}

    def drop(r, inbox):
        mask = rng.rand(cfg.groups, cfg.peers, cfg.peers) < 0.25
        return jnp.where(jnp.asarray(mask)[..., None], 0, inbox)

    def props(r, cur):
        slots = leader_slot(cur)
        cnt = np.where((slots >= 0) & (rng.rand(cfg.groups) < 0.5), 1, 0)
        return (jnp.asarray(cnt, jnp.int32),
                jnp.asarray(np.maximum(slots, 0), jnp.int32))

    inbox = None
    for chunk in range(40):
        st, inbox = run_rounds(cfg, st, 3, inbox=inbox, drop=drop,
                               props=props)
        commit = np.asarray(st.commit)
        last = np.asarray(st.last_index)
        log_term = np.asarray(st.log_term)
        for g in range(cfg.groups):
            for p in range(cfg.peers):
                c = commit[g, p]
                # walk the device window of committed entries
                lo = max(1, last[g, p] - cfg.window + 1)
                for i in range(lo, c + 1):
                    t = log_term[g, p, i % cfg.window]
                    key = (g, i)
                    prev = committed.setdefault(key, t)
                    assert prev == t, (
                        f"committed entry changed: group {g} index {i}: "
                        f"{prev} -> {t}")
        assert not np.asarray(st.need_host).any()


def test_election_timing_matches_scalar_oracle():
    """With no message delivery, campaign ticks must be bit-identical to the
    scalar core: same xorshift32 streams, same draw points."""
    G, P = 3, 3
    cfg, st = make(groups=G, peers=P)
    scalars = {}
    for g in range(G):
        for p in range(P):
            r = Raft(ScalarConfig(id=p + 1, peers=list(range(1, P + 1)),
                                  election_tick=cfg.election_tick,
                                  heartbeat_tick=cfg.heartbeat_tick,
                                  storage=MemoryStorage(), group=g))
            scalars[(g, p)] = r

    inbox = empty_inbox(cfg)
    zero = jnp.zeros(G, jnp.int32)
    for step_i in range(40):
        st, outbox = kernel.step(cfg, st, inbox, zero, zero,
                                 jnp.asarray(True))
        # NOTE: no routing — every message is dropped, scalars mirrored.
        for (g, p), r in scalars.items():
            r.tick()
            r.msgs.clear()
        term = np.asarray(st.term)
        state = np.asarray(st.state)
        for (g, p), r in scalars.items():
            assert term[g, p] == r.term, (step_i, g, p, term[g, p], r.term)
            assert state[g, p] == int(r.state), (step_i, g, p)


def test_step_is_jit_stable():
    # Same compiled function must serve different G without retrace per call
    # (static cfg implies one trace per config — just assert it runs twice).
    cfg, st = make(groups=2, peers=3)
    inbox = empty_inbox(cfg)
    zero = jnp.zeros(cfg.groups, jnp.int32)
    st, out = kernel.step(cfg, st, inbox, zero, zero, jnp.asarray(True))
    st, out2 = kernel.step(cfg, st, kernel.route_local(out), zero, zero,
                           jnp.asarray(True))
    assert out2.shape == (cfg.groups, cfg.peers, cfg.peers, cfg.fields)


def test_lost_appends_retransmitted_via_heartbeat_resp():
    """Appends to one follower are dropped while next is optimistically
    bumped past them; once the drop heals, the leader must recover via the
    heartbeat-response staleness rule (reference stepLeader MsgHeartbeatResp
    -> sendAppend, raft.go:547-551) — with no new proposals to kick the
    gap-driven sender."""
    cfg = KernelConfig(groups=2, peers=3, window=16, max_ents=2,
                       heartbeat_tick=2)
    st = init_state(cfg, stagger=True)
    st, inbox = run_rounds(cfg, st, 8)
    slots = leader_slot(st)
    assert (slots >= 0).all()
    g = np.arange(cfg.groups)
    victim = (slots + 1) % cfg.peers

    from etcd_tpu.ops.state import F_TYPE, M_APP

    def drop_apps(r, inbox):
        arr = np.array(inbox)
        is_app = arr[g, victim, :, F_TYPE] == M_APP
        arr[g, victim, :, :] = np.where(is_app[..., None], 0,
                                        arr[g, victim, :, :])
        return jnp.asarray(arr)

    def props(r, cur):
        return (jnp.full(cfg.groups, 2, jnp.int32),
                jnp.asarray(slots, jnp.int32))

    # Propose while appends to the victim vanish (acks never come back
    # because the appends never arrive; heartbeats still flow). Few enough
    # entries that the victim stays within the leader's ring window —
    # beyond it, catch-up is the host snapshot-install path (engine tests).
    st, inbox = run_rounds(cfg, st, 3, inbox=inbox, props=props,
                           drop=drop_apps)
    last = np.asarray(st.last_index)[g, slots]
    match_v = np.asarray(st.match)[g, slots, victim]
    assert (match_v < last).all(), "victim should be behind"

    # Heal, but propose NOTHING more: only the staleness rule can recover.
    st, inbox = run_rounds(cfg, st, 25, inbox=inbox)
    match_v = np.asarray(st.match)[g, slots, victim]
    last = np.asarray(st.last_index)[g, slots]
    assert (match_v == last).all(), (
        "victim not caught up after heal", match_v, last)
    commit = np.asarray(st.commit)[g, victim]
    assert (commit == last).all(), (commit, last)


def test_corrupt_commit_flags_violation():
    # The kernel carries defensive invariant detectors (the TPU-native form
    # of the reference's log.maybeAppend/commitTo panics): no legal
    # transition yields commit > last_index, so seeing it means corrupted
    # device state. It must raise NH_VIOLATION — distinct from the NH_SNAP
    # serviceable escape — so the host engine dumps state and fails loudly.
    from etcd_tpu.ops.state import NH_SNAP, NH_VIOLATION
    cfg, st = make(groups=2, peers=3)
    st, _ = run_rounds(cfg, st, 60)
    assert (leader_slot(st) >= 0).all()
    assert not np.asarray(st.need_host).any()
    # Artificial corruption: one follower's commit cursor jumps past its
    # log end.
    bad_commit = np.asarray(st.commit).copy()
    slot = 0 if leader_slot(st)[1] != 0 else 1
    bad_commit[1, slot] = int(np.asarray(st.last_index)[1, slot]) + 7
    st = st._replace(commit=jnp.asarray(bad_commit))
    st, _ = run_rounds(cfg, st, 1)
    nh = np.asarray(st.need_host)
    assert nh[1, slot] & NH_VIOLATION, nh
    # Healthy group 0 stays clean.
    assert not (nh[0] & NH_VIOLATION).any(), nh
